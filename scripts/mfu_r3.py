"""Round-3 MFU experiments on the real chip (run from /root/repo).

Slope-timed (two-point lax.scan with scalar feedback — see BASELINE.md
"Compute-harness v3" for why) components and variants:

  python scripts/mfu_r3.py baseline    # per-layer re-confirmation
  python scripts/mfu_r3.py stem        # space-to-depth stem variants
  python scripts/mfu_r3.py elemwise    # relu/residual tail cost split
  python scripts/mfu_r3.py shuffle     # pixel-shuffle orderings
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

B, H, W = 8, 720, 1280
F = 128


def slope_time(fn, x0, lo=4, hi=12, reps=4):
    """Seconds per iteration of `fn`, dispatch floor cancelled."""

    def rollout(iters):
        def step(x, _):
            out = fn(x)
            # feedback must consume ALL of out: a scalar SLICE lets XLA
            # narrow a single conv to computing one output pixel (a lone
            # body conv "measures" 5.4 ms = 402 TFLOP/s, 2x over peak).
            # A mean reduction forces the full output at ~0.4 ms/step of
            # uniform harness cost.
            return x + jnp.mean(out).astype(x.dtype), ()

        def run(x):
            final, _ = jax.lax.scan(step, x, None, length=iters)
            return jnp.sum(final)

        return jax.jit(run)

    run_lo, run_hi = rollout(lo), rollout(hi)
    jax.device_get(run_lo(x0))
    jax.device_get(run_hi(x0))
    best = None
    for _ in range(reps):
        t0 = time.monotonic()
        jax.device_get(run_lo(x0))
        t1 = time.monotonic()
        jax.device_get(run_hi(x0))
        t2 = time.monotonic()
        dt = ((t2 - t1) - (t1 - t0)) / (hi - lo)
        best = dt if best is None else min(best, dt)
    return best


def conv(x, kh, kw, cin, cout, key=0):
    k = jax.random.normal(jax.random.PRNGKey(key), (kh, kw, cin, cout),
                          jnp.bfloat16) * 0.05
    return jax.lax.conv_general_dilated(
        x, k, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))


def s2d(x, r):
    b, h, w, c = x.shape
    x = x.reshape(b, h // r, r, w // r, r, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // r, w // r, r * r * c)


def d2s(x, r):
    b, h, w, c_full = x.shape
    c = c_full // (r * r)
    x = x.reshape(b, h, w, r, r, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h * r, w * r, c)


def compare(variants, x0, lo=4, hi=12, reps=4):
    """Interleaved slope timing: one rep of every variant per round, so
    shared-chip drift hits all variants equally.  Returns ms/iter each."""

    def rollout(fn, iters):
        def step(x, _):
            out = fn(x)
            return x + jnp.mean(out).astype(x.dtype), ()

        def run(x):
            final, _ = jax.lax.scan(step, x, None, length=iters)
            return jnp.sum(final)

        return jax.jit(run)

    compiled = {}
    for name, fn in variants.items():
        compiled[name] = (rollout(fn, lo), rollout(fn, hi))
        jax.device_get(compiled[name][0](x0))
        jax.device_get(compiled[name][1](x0))
    best = {name: None for name in variants}
    for _ in range(reps):
        for name, (run_lo, run_hi) in compiled.items():
            t0 = time.monotonic()
            jax.device_get(run_lo(x0))
            t1 = time.monotonic()
            jax.device_get(run_hi(x0))
            t2 = time.monotonic()
            dt = ((t2 - t1) - (t1 - t0)) / (hi - lo) * 1e3
            if best[name] is None or dt < best[name]:
                best[name] = dt
    return best


def model_variants():
    """Full-model variants sharing the body/head; stems differ."""

    def body_and_head(x, relu_residual=True):
        for i in range(3):
            h = conv(x, 3, 3, F, F, key=10 + i)
            x = jax.nn.relu(h) + x if relu_residual else h
        x = conv(x, 3, 3, F, 12, key=20)
        return d2s(x, 2)

    def current(x):
        h = jax.nn.relu(conv(x, 5, 5, 3, F, key=1))
        return body_and_head(h)

    def s2d_stem3(x):
        h = d2s(conv(s2d(x, 2), 3, 3, 12, 4 * F, key=1), 2)
        return body_and_head(jax.nn.relu(h))

    def s2d_stem5(x):
        h = d2s(conv(s2d(x, 2), 5, 5, 12, 4 * F, key=1), 2)
        return body_and_head(jax.nn.relu(h))

    def no_elemwise(x):
        # bound for the relu/residual tail cost in-model
        h = conv(x, 5, 5, 3, F, key=1)
        return body_and_head(h, relu_residual=False)

    return {
        "current": current,
        "s2d_stem3": s2d_stem3,
        "s2d_stem5": s2d_stem5,
        "no_elemwise": no_elemwise,
    }


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "baseline"
    out = {"experiment": which, "backend": jax.default_backend(),
           "device": jax.devices()[0].device_kind}
    rng = jax.random.PRNGKey(0)
    x3 = jax.random.uniform(rng, (B, H, W, 3), jnp.float32).astype(jnp.bfloat16)
    xf = jax.random.uniform(rng, (B, H, W, F), jnp.float32).astype(jnp.bfloat16)

    if which == "baseline":
        from downloader_tpu.compute.models.upscaler import (
            UpscalerConfig, init_params,
        )
        config = UpscalerConfig()
        model, params = init_params(rng, config)
        out["full_model_ms"] = slope_time(
            lambda x: model.apply(params, x), x3) * 1e3
        out["stem5x5_c3_ms"] = slope_time(
            lambda x: conv(x, 5, 5, 3, F), x3) * 1e3
        out["body3x3_ms"] = slope_time(
            lambda x: conv(x, 3, 3, F, F), xf) * 1e3
        out["head3x3_ms"] = slope_time(
            lambda x: conv(x, 3, 3, F, 12), xf) * 1e3

    elif which == "compare":
        results = compare(model_variants(), x3)
        out.update({f"{k}_ms": v for k, v in results.items()})

    elif which == "compare2":
        # the one close call, longer: current vs s2d stem, 8 rounds
        variants = model_variants()
        results = compare(
            {k: variants[k] for k in ("current", "s2d_stem3")},
            x3, lo=4, hi=16, reps=8,
        )
        out.update({f"{k}_ms": v for k, v in results.items()})

    elif which == "stem":
        out["stem5x5_c3_ms"] = slope_time(
            lambda x: conv(x, 5, 5, 3, F), x3) * 1e3
        out["stem3x3_c3_ms"] = slope_time(
            lambda x: conv(x, 3, 3, 3, F), x3) * 1e3
        # fold 2x2 -> conv at half res with C_in=12 -> unfold
        out["s2d2_conv3_d2s_ms"] = slope_time(
            lambda x: d2s(conv(s2d(x, 2), 3, 3, 12, 4 * F), 2), x3) * 1e3
        out["s2d2_conv5_d2s_ms"] = slope_time(
            lambda x: d2s(conv(s2d(x, 2), 5, 5, 12, 4 * F), 2), x3) * 1e3
        # s2d cost alone (layout), and conv alone on pre-folded input
        x12 = jax.random.uniform(
            rng, (B, H // 2, W // 2, 12), jnp.float32).astype(jnp.bfloat16)
        out["s2d2_alone_ms"] = slope_time(lambda x: s2d(x, 2), x3) * 1e3
        out["conv3_c12_f512_ms"] = slope_time(
            lambda x: conv(x, 3, 3, 12, 4 * F), x12) * 1e3
        x48 = jax.random.uniform(
            rng, (B, H // 4, W // 4, 48), jnp.float32).astype(jnp.bfloat16)
        out["conv3_c48_f2048_ms"] = slope_time(
            lambda x: conv(x, 3, 3, 48, 16 * F), x48) * 1e3

    elif which == "elemwise":
        def body_plain(x):
            for i in range(3):
                x = conv(x, 3, 3, F, F, key=i)
            return x

        def body_relu(x):
            for i in range(3):
                x = jax.nn.relu(conv(x, 3, 3, F, F, key=i))
            return x

        def body_full(x):
            for i in range(3):
                x = jax.nn.relu(conv(x, 3, 3, F, F, key=i)) + x
            return x

        def body_maxadd(x):
            # same math, different association: relu into the add
            for i in range(3):
                x = jnp.maximum(conv(x, 3, 3, F, F, key=i), 0.0) + x
            return x

        out["body3_plain_ms"] = slope_time(body_plain, xf) * 1e3
        out["body3_relu_ms"] = slope_time(body_relu, xf) * 1e3
        out["body3_relu_residual_ms"] = slope_time(body_full, xf) * 1e3
        out["body3_maxadd_ms"] = slope_time(body_maxadd, xf) * 1e3

    elif which == "stage":
        # the v4 harness exposed a ~30% stage tail (chroma/colorspace/
        # quantize) around the model.  Variants of the FULL stage fn,
        # interleaved, feedback summed through the nonlinear quantize.
        import numpy as np

        from downloader_tpu.compute.models.upscaler import (
            UpscalerConfig, init_params,
        )
        from downloader_tpu.compute.ops.pixel_shuffle import quantize_u8

        config = UpscalerConfig()
        model, params = init_params(rng, config)
        h, w = 720, 1280
        host = np.random.default_rng(0)
        y0 = jnp.asarray(host.integers(0, 256, (B, h, w), np.uint8))
        cb0 = jnp.asarray(host.integers(0, 256, (B, h // 2, w // 2), np.uint8))
        cr0 = jnp.asarray(host.integers(0, 256, (B, h // 2, w // 2), np.uint8))

        def up2(p):  # nearest-neighbor chroma upsample
            return jnp.repeat(jnp.repeat(p, 2, axis=1), 2, axis=2)

        def down2(p):
            b, hh, ww = p.shape
            return p.reshape(b, hh // 2, 2, ww // 2, 2).mean(axis=(2, 4))

        def stage_current(y, cb, cr):
            from downloader_tpu.compute.ops.colorspace import (
                rgb_to_ycbcr, ycbcr_to_rgb,
            )
            yf = y.astype(jnp.float32)
            cbf = up2(cb.astype(jnp.float32))
            crf = up2(cr.astype(jnp.float32))
            rgb = ycbcr_to_rgb(yf, cbf, crf) / 255.0
            out = model.apply(params, rgb)
            y2, cb2, cr2 = rgb_to_ycbcr(out.astype(jnp.float32) * 255.0)
            return quantize_u8(y2), quantize_u8(down2(cb2)), quantize_u8(down2(cr2))

        def stage_planes_f32(y, cb, cr):
            # plane-wise lincomb: no lane-dim-3 stack/matmul; /255 and
            # *255 folded into the coefficients
            yf = y.astype(jnp.float32) * (1.0 / 255.0)
            cbf = up2(cb.astype(jnp.float32) - 128.0) * (1.0 / 255.0)
            crf = up2(cr.astype(jnp.float32) - 128.0) * (1.0 / 255.0)
            r = yf + 1.402 * crf
            g = yf - 0.344136 * cbf - 0.714136 * crf
            b = yf + 1.772 * cbf
            rgb = jnp.stack([r, g, b], axis=-1)
            out = model.apply(params, rgb).astype(jnp.float32)
            ro, go, bo = out[..., 0], out[..., 1], out[..., 2]
            y2 = (0.299 * ro + 0.587 * go + 0.114 * bo) * 255.0
            cb2 = (-0.168736 * ro - 0.331264 * go + 0.5 * bo) * 255.0 + 128.0
            cr2 = (0.5 * ro - 0.418688 * go - 0.081312 * bo) * 255.0 + 128.0
            return quantize_u8(y2), quantize_u8(down2(cb2)), quantize_u8(down2(cr2))

        def stage_planes_bf16(y, cb, cr):
            dt = jnp.bfloat16
            yf = y.astype(dt) * dt(1.0 / 255.0)
            cbf = up2(cb.astype(dt) - dt(128.0)) * dt(1.0 / 255.0)
            crf = up2(cr.astype(dt) - dt(128.0)) * dt(1.0 / 255.0)
            r = yf + dt(1.402) * crf
            g = yf - dt(0.344136) * cbf - dt(0.714136) * crf
            b = yf + dt(1.772) * cbf
            rgb = jnp.stack([r, g, b], axis=-1)
            out = model.apply(params, rgb).astype(jnp.float32)
            ro, go, bo = out[..., 0], out[..., 1], out[..., 2]
            y2 = (0.299 * ro + 0.587 * go + 0.114 * bo) * 255.0
            cb2 = (-0.168736 * ro - 0.331264 * go + 0.5 * bo) * 255.0 + 128.0
            cr2 = (0.5 * ro - 0.418688 * go - 0.081312 * bo) * 255.0 + 128.0
            return quantize_u8(y2), quantize_u8(down2(cb2)), quantize_u8(down2(cr2))

        def rollout(fn, iters):
            fn = jax.jit(fn)  # nested jit, like the real engine's
            # _compiled fn — Pallas quantize traced bare in a scan body
            # leaks tracers on TPU

            def step(s, _):
                y2, cb2, cr2 = fn(y0 + s, cb0 + s, cr0 + s)
                total = (jnp.sum(y2, dtype=jnp.int32)
                         + jnp.sum(cb2, dtype=jnp.int32)
                         + jnp.sum(cr2, dtype=jnp.int32))
                return total.astype(jnp.uint8), ()

            def run():
                final, _ = jax.lax.scan(step, jnp.uint8(0), None, length=iters)
                return final

            return jax.jit(run)

        fns = {"stage_current": stage_current,
               "stage_planes_f32": stage_planes_f32,
               "stage_planes_bf16": stage_planes_bf16}
        lo_i, hi_i = 4, 12
        compiled = {}
        for name, fn in fns.items():  # compile once per (fn, iters)
            lo_f, hi_f = rollout(fn, lo_i), rollout(fn, hi_i)
            jax.device_get(lo_f())
            jax.device_get(hi_f())
            compiled[name] = (lo_f, hi_f)
        best = {name: None for name in fns}
        for _ in range(4):  # interleaved: drift hits all variants equally
            for name, (lo_f, hi_f) in compiled.items():
                t0 = time.monotonic()
                jax.device_get(lo_f())
                t1 = time.monotonic()
                jax.device_get(hi_f())
                t2 = time.monotonic()
                dt_ms = ((t2 - t1) - (t1 - t0)) / (hi_i - lo_i) * 1e3
                if best[name] is None or dt_ms < best[name]:
                    best[name] = dt_ms
        out.update({f"{k}_ms": round(v, 3) for k, v in best.items()})

    elif which == "stage2":
        # subpixel-domain tail: colorspace+quantize at 720p BEFORE the
        # shuffle.  Chroma: downsample(shuffle(x)) by r == mean over
        # each r*r subpixel channel group (box filter commutes with the
        # shuffle), so the 1440p chroma planes are never materialized;
        # luma: transform+quantize the 4 subpixel channels at 720p, then
        # shuffle u8 bytes (4x less relayout traffic than f32).
        import numpy as np

        from downloader_tpu.compute.ops.colorspace import (  # noqa: F401
            rgb_to_ycbcr, ycbcr_to_rgb,
        )
        from downloader_tpu.compute.ops.pixel_shuffle import quantize_u8

        h, w = 720, 1280
        host = np.random.default_rng(0)
        y0 = jnp.asarray(host.integers(0, 256, (B, h, w), np.uint8))
        cb0 = jnp.asarray(host.integers(0, 256, (B, h // 2, w // 2), np.uint8))
        cr0 = jnp.asarray(host.integers(0, 256, (B, h // 2, w // 2), np.uint8))

        def up2(p):
            return jnp.repeat(jnp.repeat(p, 2, axis=1), 2, axis=2)

        def down2(p):
            b, hh, ww = p.shape
            return p.reshape(b, hh // 2, 2, ww // 2, 2).mean(axis=(2, 4))

        def backbone(x):
            x = x.astype(jnp.bfloat16)  # the model casts internally too
            x = jax.nn.relu(conv(x, 5, 5, 3, F, key=1))
            for i in range(3):
                x = jax.nn.relu(conv(x, 3, 3, F, F, key=10 + i)) + x
            return conv(x, 3, 3, F, 12, key=20)  # (B, h, w, 12) pre-shuffle

        def front(y, cb, cr):
            from downloader_tpu.compute.ops.colorspace import ycbcr_to_rgb
            yf = y.astype(jnp.float32)
            cbf = up2(cb.astype(jnp.float32))
            crf = up2(cr.astype(jnp.float32))
            return ycbcr_to_rgb(yf, cbf, crf) / 255.0

        def stage_current_raw(y, cb, cr):
            from downloader_tpu.compute.ops.colorspace import rgb_to_ycbcr
            out = d2s(backbone(front(y, cb, cr)), 2)
            y2, cb2, cr2 = rgb_to_ycbcr(out.astype(jnp.float32) * 255.0)
            return (quantize_u8(y2), quantize_u8(down2(cb2)),
                    quantize_u8(down2(cr2)))

        def stage_subpixel(y, cb, cr):
            h12 = backbone(front(y, cb, cr)).astype(jnp.float32) * 255.0
            b, hh, ww, _ = h12.shape
            sub = h12.reshape(b, hh, ww, 4, 3)  # (di*2+dj, rgb)
            y_sub = (0.299 * sub[..., 0] + 0.587 * sub[..., 1]
                     + 0.114 * sub[..., 2])           # (b, h, w, 4)
            y_u8 = quantize_u8(y_sub)
            y2 = y_u8.reshape(b, hh, ww, 2, 2).transpose(
                0, 1, 3, 2, 4).reshape(b, hh * 2, ww * 2)
            mean_rgb = sub.mean(axis=3)               # (b, h, w, 3)
            cb2 = (-0.168736 * mean_rgb[..., 0] - 0.331264 * mean_rgb[..., 1]
                   + 0.5 * mean_rgb[..., 2]) + 128.0
            cr2 = (0.5 * mean_rgb[..., 0] - 0.418688 * mean_rgb[..., 1]
                   - 0.081312 * mean_rgb[..., 2]) + 128.0
            return y2, quantize_u8(cb2), quantize_u8(cr2)

        def rollout(fn, iters):
            fn = jax.jit(fn)

            def step(s, _):
                y2, cb2, cr2 = fn(y0 + s, cb0 + s, cr0 + s)
                total = (jnp.sum(y2, dtype=jnp.int32)
                         + jnp.sum(cb2, dtype=jnp.int32)
                         + jnp.sum(cr2, dtype=jnp.int32))
                return total.astype(jnp.uint8), ()

            def run():
                final, _ = jax.lax.scan(step, jnp.uint8(0), None, length=iters)
                return final

            return jax.jit(run)

        fns = {"stage_current_raw": stage_current_raw,
               "stage_subpixel": stage_subpixel}
        lo_i, hi_i = 4, 12
        compiled = {}
        for name, fn in fns.items():
            lo_f, hi_f = rollout(fn, lo_i), rollout(fn, hi_i)
            jax.device_get(lo_f())
            jax.device_get(hi_f())
            compiled[name] = (lo_f, hi_f)
        best = {name: None for name in fns}
        for _ in range(4):
            for name, (lo_f, hi_f) in compiled.items():
                t0 = time.monotonic()
                jax.device_get(lo_f())
                t1 = time.monotonic()
                jax.device_get(hi_f())
                t2 = time.monotonic()
                dt_ms = ((t2 - t1) - (t1 - t0)) / (hi_i - lo_i) * 1e3
                if best[name] is None or dt_ms < best[name]:
                    best[name] = dt_ms
        out.update({f"{k}_ms": round(v, 3) for k, v in best.items()})

    elif which == "stage3":
        # shave the remaining input-side tail: fold /255 into the
        # colorspace matrix+bias (one less full-tensor pass) and try the
        # input colorspace in bf16 (the model casts to bf16 anyway)
        import numpy as np

        from downloader_tpu.compute.ops.colorspace import (
            fused_subpixel_ycc, ycbcr_to_rgb, ycbcr_to_unit_rgb,
        )

        h, w = 720, 1280
        host = np.random.default_rng(0)
        y0 = jnp.asarray(host.integers(0, 256, (B, h, w), np.uint8))
        cb0 = jnp.asarray(host.integers(0, 256, (B, h // 2, w // 2), np.uint8))
        cr0 = jnp.asarray(host.integers(0, 256, (B, h // 2, w // 2), np.uint8))

        def up2(p):
            return jnp.repeat(jnp.repeat(p, 2, axis=1), 2, axis=2)

        def backbone(x):
            x = x.astype(jnp.bfloat16)
            x = jax.nn.relu(conv(x, 5, 5, 3, F, key=1))
            for i in range(3):
                x = jax.nn.relu(conv(x, 3, 3, F, F, key=10 + i)) + x
            return conv(x, 3, 3, F, 12, key=20)

        from downloader_tpu.compute.ops.colorspace import (
            _YCC2RGB_UNIT, _YCC2RGB_UNIT_BIAS,
        )

        def front_current(y, cb, cr):
            # the pre-fold front: separate /255 pass
            yf = y.astype(jnp.float32)
            cbf = up2(cb.astype(jnp.float32))
            crf = up2(cr.astype(jnp.float32))
            return ycbcr_to_rgb(yf, cbf, crf) / 255.0

        def front_folded(y, cb, cr):
            # THE SHIPPED transform (one source of truth)
            return ycbcr_to_unit_rgb(
                y.astype(jnp.float32),
                up2(cb.astype(jnp.float32)),
                up2(cr.astype(jnp.float32)))

        def front_folded_bf16(y, cb, cr):
            ycc = jnp.stack(
                [y.astype(jnp.bfloat16),
                 up2(cb.astype(jnp.bfloat16)),
                 up2(cr.astype(jnp.bfloat16))], axis=-1)
            return (ycc @ jnp.asarray(_YCC2RGB_UNIT, jnp.bfloat16).T
                    + jnp.asarray(_YCC2RGB_UNIT_BIAS, jnp.bfloat16))

        def make_stage(front):
            def fn(y, cb, cr):
                # unit-domain contract: fused_subpixel_ycc folds the
                # display scaling into its coefficients
                return fused_subpixel_ycc(backbone(front(y, cb, cr)), 2)
            return fn

        def rollout(fn, iters):
            fn = jax.jit(fn)

            def step(s, _):
                y2, cb2, cr2 = fn(y0 + s, cb0 + s, cr0 + s)
                total = (jnp.sum(y2, dtype=jnp.int32)
                         + jnp.sum(cb2, dtype=jnp.int32)
                         + jnp.sum(cr2, dtype=jnp.int32))
                return total.astype(jnp.uint8), ()

            def run():
                final, _ = jax.lax.scan(step, jnp.uint8(0), None, length=iters)
                return final

            return jax.jit(run)

        fns = {"front_current": make_stage(front_current),
               "front_folded": make_stage(front_folded),
               "front_folded_bf16": make_stage(front_folded_bf16)}
        lo_i, hi_i = 4, 12
        compiled = {}
        for name, fn in fns.items():
            lo_f, hi_f = rollout(fn, lo_i), rollout(fn, hi_i)
            jax.device_get(lo_f())
            jax.device_get(hi_f())
            compiled[name] = (lo_f, hi_f)
        best = {name: None for name in fns}
        for _ in range(4):
            for name, (lo_f, hi_f) in compiled.items():
                t0 = time.monotonic()
                jax.device_get(lo_f())
                t1 = time.monotonic()
                jax.device_get(hi_f())
                t2 = time.monotonic()
                dt_ms = ((t2 - t1) - (t1 - t0)) / (hi_i - lo_i) * 1e3
                if best[name] is None or dt_ms < best[name]:
                    best[name] = dt_ms
        out.update({f"{k}_ms": round(v, 3) for k, v in best.items()})

    elif which == "shuffle":
        x12 = jax.random.uniform(
            rng, (B, H, W, 12), jnp.float32).astype(jnp.bfloat16)

        def shuffle_rrc(x):  # channel order (r, r, c) — current impl
            b, h, w, _ = x.shape
            x = x.reshape(b, h, w, 2, 2, 3)
            x = x.transpose(0, 1, 3, 2, 4, 5)
            return x.reshape(b, h * 2, w * 2, 3)

        def shuffle_crr(x):  # channel order (c, r, r)
            b, h, w, _ = x.shape
            x = x.reshape(b, h, w, 3, 2, 2)
            x = x.transpose(0, 1, 4, 2, 5, 3)
            return x.reshape(b, h * 2, w * 2, 3)

        out["shuffle_rrc_ms"] = slope_time(shuffle_rrc, x12) * 1e3
        out["shuffle_crr_ms"] = slope_time(shuffle_crr, x12) * 1e3
        # head conv + shuffle fused vs separate
        out["head_plus_shuffle_ms"] = slope_time(
            lambda x: shuffle_rrc(conv(x, 3, 3, F, 12)), xf) * 1e3

    print(json.dumps({k: (round(v, 3) if isinstance(v, float) else v)
                      for k, v in out.items()}))


if __name__ == "__main__":
    main()
