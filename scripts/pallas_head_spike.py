"""r5 spike: Pallas kernel for the s2d head (stride-2 4x4 conv 128->48).

XLA's lowering of this conv re-reads the trunk output ~4x (input-
bandwidth bound, ~13 ms of a ~67 ms step — BASELINE.md "The r5
budget").  A Pallas kernel reads each input element once into VMEM and
expresses the conv as 16 strided (1024,128)@(128,48) dots, targeting
the ~5-6 ms single-read bound.

Overlap handling without element-indexed BlockSpecs: the input is
pre-padded outside the kernel with the SAME-conv zeros (+1 top/left)
and rounded up to a block multiple bottom/right, then each grid cell
loads its own block PLUS its right/bottom/corner neighbors (index maps
clamp at the edge, where the clamped reads hit real zero rows) — no
in-kernel masking needed.

Run: python scripts/pallas_head_spike.py [check|race]
"""

import functools
import sys
import time

import numpy as np

sys.path.insert(0, "/root/repo")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402

BM = 8    # output rows per block
BN = 64   # output cols per block


def _kernel(nh, nw, cin, cout, out_dtype,
            x_ref, xr_ref, xb_ref, xc_ref, k_ref, b_ref, o_ref):
    # the input is pre-padded with the SAME-conv zeros and rounded up to
    # a block multiple, so neighbor reads never need masking
    x = x_ref[0]                       # (2BM, 2BN, C)
    right = xr_ref[0][:, :2, :]        # (2BM, 2, C)
    bottom = xb_ref[0][:2, :, :]       # (2, 2BN, C)
    corner = xc_ref[0][:2, :2, :]      # (2, 2, C)
    top = jnp.concatenate([x, right], axis=1)          # (2BM, 2BN+2, C)
    bot = jnp.concatenate([bottom, corner], axis=1)    # (2, 2BN+2, C)
    xt = jnp.concatenate([top, bot], axis=0)           # (2BM+2, 2BN+2, C)
    # stride-2 access via parity reshape: strided slices lower to
    # (unsupported) gathers in Mosaic, unit-stride slices don't
    xt4 = xt.reshape(BM + 1, 2, BN + 1, 2, cin)
    acc = jnp.zeros((BM * BN, cout), jnp.float32)
    for u in range(4):
        for v in range(4):
            p, du = u % 2, u // 2
            q, dv = v % 2, v // 2
            xs = xt4[du:du + BM, p, dv:dv + BN, q, :]
            acc = acc + jnp.dot(
                xs.reshape(BM * BN, cin), k_ref[u, v],
                preferred_element_type=jnp.float32)
    out = acc + b_ref[0].astype(jnp.float32)
    o_ref[0] = out.reshape(BM, BN, cout).astype(out_dtype)


def pallas_s2d_head(feats, k4, bias4, out_dtype=jnp.bfloat16):
    """feats (B, H, W, C) -> (B, H/2, W/2, 4*C_head) like ops.s2d_head."""
    b, h, w, cin = feats.shape
    cout = k4.shape[-1]
    h2, w2 = h // 2, w // 2
    nh, nw = h2 // BM, w2 // BN
    assert h2 % BM == 0 and w2 % BN == 0, (h2, w2)
    grid = (b, nh, nw)
    # SAME-padding zeros up front (+1 top/left), rounded up to a full
    # extra block bottom/right so the clamped neighbor reads hit real
    # zeros instead of needing in-kernel masks
    feats = jnp.pad(feats, ((0, 0), (1, 2 * BM - 1), (1, 2 * BN - 1),
                            (0, 0)))
    nh_in = feats.shape[1] // (2 * BM)
    nw_in = feats.shape[2] // (2 * BN)

    def im_x(bi, i, j):
        return (bi, i, j, 0)

    def im_right(bi, i, j):
        return (bi, i, jnp.minimum(j + 1, nw_in - 1), 0)

    def im_bottom(bi, i, j):
        return (bi, jnp.minimum(i + 1, nh_in - 1), j, 0)

    def im_corner(bi, i, j):
        return (bi, jnp.minimum(i + 1, nh_in - 1),
                jnp.minimum(j + 1, nw_in - 1), 0)

    block = (1, 2 * BM, 2 * BN, cin)
    kern = functools.partial(_kernel, nh, nw, cin, cout, out_dtype)
    return pl.pallas_call(
        kern,
        grid=grid,
        in_specs=[
            pl.BlockSpec(block, im_x),
            pl.BlockSpec(block, im_right),
            pl.BlockSpec(block, im_bottom),
            pl.BlockSpec(block, im_corner),
            pl.BlockSpec((4, 4, cin, cout), lambda bi, i, j: (0, 0, 0, 0)),
            pl.BlockSpec((1, cout), lambda bi, i, j: (0, 0)),
        ],
        out_specs=pl.BlockSpec((1, BM, BN, cout),
                               lambda bi, i, j: (bi, i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h2, w2, cout), out_dtype),
    )(feats, feats, feats, feats, k4, bias4[None])


def main():
    from downloader_tpu.compute.ops.s2d_head import pack_s2d_kernel, s2d_head
    from downloader_tpu.compute.pipeline import FrameUpscaler

    mode = sys.argv[1] if len(sys.argv) > 1 else "check"
    eng = FrameUpscaler(batch=8, use_mesh=False)
    head = eng.params["params"]["subpixel"]
    k4 = pack_s2d_kernel(head["kernel"]).astype(jnp.bfloat16)
    bias4 = jnp.tile(head["bias"], 4).astype(jnp.bfloat16)
    print("backend:", jax.default_backend(), flush=True)

    if mode == "check":
        rng = np.random.default_rng(0)
        feats = jnp.asarray(
            rng.standard_normal((2, 64, 256, 128)), jnp.bfloat16)
        want = s2d_head(feats, head["kernel"], head["bias"])
        got = pallas_s2d_head(feats, k4, bias4)
        w32 = np.asarray(want, np.float32)
        g32 = np.asarray(got, np.float32)
        print("shapes:", want.shape, got.shape)
        print("max |diff|:", float(np.abs(w32 - g32).max()))
        print("exact frac:", float((w32 == g32).mean()))
    elif mode == "race":
        feats = jnp.asarray(
            np.random.default_rng(0).standard_normal((8, 720, 1280, 128)),
            jnp.bfloat16)

        def run_xla(f):
            return jnp.sum(s2d_head(f, head["kernel"], head["bias"])
                           .astype(jnp.float32))

        def run_pallas(f):
            return jnp.sum(pallas_s2d_head(f, k4, bias4)
                           .astype(jnp.float32))

        def scan_runner(body, iters=20):
            def rollout(f):
                def step(s, _):
                    # genuine dependence: the sum feeds the next input
                    # scaled to ~0 so values stay finite — a *0 feedback
                    # would let XLA elide the non-pallas variant
                    total = body(f + s)
                    return (total * 1e-30).astype(jnp.bfloat16), ()
                final, _ = jax.lax.scan(
                    step, jnp.bfloat16(0), None, length=iters)
                return final
            run = jax.jit(rollout)
            jax.device_get(run(feats))
            def timed():
                t0 = time.monotonic()
                jax.device_get(run(feats))
                return (time.monotonic() - t0) / iters
            return timed

        variants = [("xla_head", scan_runner(run_xla)),
                    ("pallas_head", scan_runner(run_pallas))]
        best = {n: float("inf") for n, _ in variants}
        for _ in range(4):
            for n, t in variants:
                best[n] = min(best[n], t())
        for n, v in best.items():
            print(f"{n}: {v*1000:7.2f} ms")


if __name__ == "__main__":
    main()
