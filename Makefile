# Build/test entry points (reference Makefile renders CI config,
# /root/reference/Makefile:1-7; here make drives the whole dev loop).

.PHONY: test bench proto lint run docker

test:
	python -m pytest tests/ -x -q

lint:
	python -m pytest tests/test_lint.py -q

bench:
	python bench.py

# regenerate protobuf gencode after editing downloader.proto
proto:
	protoc --python_out=downloader_tpu/schemas --proto_path=downloader_tpu/schemas downloader.proto

run:
	python -m downloader_tpu

docker:
	docker build -t downloader-tpu .
