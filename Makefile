# Build/test entry points (reference Makefile renders CI config,
# /root/reference/Makefile:1-7; here make drives the whole dev loop).

.PHONY: test bench bench-overlap bench-fleet chaos fleet proto lint run docker integration

# hermetic gate: never touches localhost services, even when something
# happens to be listening on 5672/9000
test:
	python -m pytest tests/ -x -q -m "not integration"

# opt-in: real RabbitMQ + MinIO (docker compose up -d --wait first);
# the tests auto-skip when the services are unreachable
integration:
	python -m pytest tests/ -m integration -v

# fault-injection chaos suite: the taxonomy/retry/breaker layer proven
# against deterministic store/publish/http/tracker/disk failures
chaos:
	python -m pytest tests/test_faults.py -v

# multi-worker fleet suite: coordination-store semantics, N-orchestrator
# coalescing over MiniS3, lease takeover, coord-store chaos
fleet:
	python -m pytest tests/test_fleet.py -v

lint:
	python -m pytest tests/test_lint.py -q

bench:
	python bench.py

# standalone streaming-vs-barrier stage-overlap bench (one JSON line:
# stage_overlap_speedup must stay >= 1.25, time_to_staged_ms alongside)
bench-overlap:
	python bench.py --overlap

# standalone fleet-coordination bench (one JSON line: M workers x same
# hot content, fleet_origin_bytes_ratio must stay >= 2.0)
bench-fleet:
	python bench.py --fleet

# regenerate protobuf gencode after editing downloader.proto
proto:
	protoc --python_out=downloader_tpu/schemas --proto_path=downloader_tpu/schemas downloader.proto

run:
	python -m downloader_tpu

docker:
	docker build -t downloader-tpu .
