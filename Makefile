# Build/test entry points (reference Makefile renders CI config,
# /root/reference/Makefile:1-7; here make drives the whole dev loop).

.PHONY: test bench bench-overlap bench-fleet bench-fairness bench-crash bench-obs bench-racing bench-soak bench-degraded bench-disk bench-slo bench-zerocopy bench-multichip bench-incident compute-shard chaos crash degraded disk fleet fleet-v2 incident fuzz-scenarios obs origins scrub slo soak soak-smoke soak-full proto lint run docker integration

# hermetic gate: never touches localhost services, even when something
# happens to be listening on 5672/9000
test:
	python -m pytest tests/ -x -q -m "not integration and not slow"

# opt-in: real RabbitMQ + MinIO (docker compose up -d --wait first);
# the tests auto-skip when the services are unreachable
integration:
	python -m pytest tests/ -m integration -v

# fault-injection chaos suite: the taxonomy/retry/breaker layer proven
# against deterministic store/publish/http/tracker/disk failures
chaos:
	python -m pytest tests/test_faults.py -v

# kill-based crash-chaos suite: a real worker subprocess SIGKILLed at
# chosen seams (mid-download, mid-upload, pre-ack, lease-holder) and
# restarted; asserts DONE exactly once, staged bytes hash-identical,
# no orphan workdirs/leases, retry counters monotone across the kill
crash:
	python -m pytest tests/test_crash.py tests/test_journal.py -v

# degraded-world chaos suite (ISSUE 14): windowed brownout/partition/
# flap fault kinds, the slow-call breaker policy (a latency-only store
# brownout must open the breaker with reason "slow" and shed via
# park-then-nack), asymmetric-partition degradation + GC stand-down,
# split-brain fencing at every cross-worker write (a stalled leader
# resumed mid-takeover must lose), the fleet.max_wait aging fix, and
# the degraded soak scenario (SIGSTOP stall past the lease TTL against
# a real 2-worker subprocess fleet)
degraded:
	python -m pytest tests/test_degraded.py -v

# storage fault plane suite (ISSUE 20): the disk fault kind + VFS shim
# (ENOSPC/EIO/short/latency/torn at the landing, spill, promote and
# sidecar seams), fsync-before-rename crash consistency + boot-time
# torn-tail demotion, the background scrubber (clean/repair/quarantine,
# copy-on-repair fresh inodes for hardlinked entries), and disk-full
# graceful degradation (workdir free-space admission floors, the disk
# breaker force-open, BULK shed via cache_headroom_bytes)
disk:
	python -m pytest tests/test_disk.py -v

# one full scrub pass over the local store, from the CLI (point
# DOWNLOADER_CONFIG at the instance config first; repairs pull from the
# shared tier, mismatches without a healthy replica are quarantined)
scrub:
	python -m downloader_tpu.cli scrub

# multi-worker fleet suite: coordination-store semantics, N-orchestrator
# coalescing over MiniS3, lease takeover, coord-store chaos
fleet:
	python -m pytest tests/test_fleet.py -v

# fleet data plane v2 suite (ISSUE 17): conditional-put CAS backend +
# watch/subscribe (event vs poll equivalence, brownout degradation),
# the content router decision table, the elected placement/autoscale
# controller (decision-table units + CAS-published plan), the shared
# origin-health table cold-start win, and the 3-worker same-content
# routing acceptance rig
fleet-v2:
	python -m pytest tests/test_fleet_v2.py -v

# observability suite: flight recorder + runtime introspection
# (test_obs) plus the fleet-wide trace/RED/hop-ledger layer
# (test_trace: 3-worker trace assembly, degraded-mode local-only view,
# RED histogram seams, hop-ledger attribution)
obs:
	python -m pytest tests/test_obs.py tests/test_trace.py -v

# origin-plane suite: multi-origin racing fetch (work-stealing ranges,
# per-origin breakers, straggler duplication, failover) + HLS-style
# segment-manifest ingest (live polling, VOD fast path, live window,
# overlap acceptance through the full orchestrator)
origins:
	python -m pytest tests/test_origins.py -v

# sustained-load soak suite (downloader_tpu/soak, ISSUE 13): a real
# multi-worker subprocess fleet under the full mixed workload (fan-in +
# racing + manifest + BULK deadlines) with SIGKILL chaos, held to hard
# SLO guards — p99 time-to-staged per class, bounded journal/coord/
# cache/RSS growth, zero leaked leases or orphan workdirs at drain,
# hop-ledger reconciliation.  `soak` runs the slow capacity profile
# (300 jobs, 3 workers, 3 kills); `soak-smoke` is the <60 s tier-1
# profile plus the harness's own unit tests.  Resize either with the
# soak.* knobs (docs/OPERATIONS.md "Capacity & SLOs").
soak:
	python -m pytest tests/test_soak.py -v -m slow

soak-smoke:
	python -m pytest tests/test_soak.py -v -m "not slow"

# the full 100k-job capacity profile (ROADMAP item 5's standing entry
# point): the same test_soak_full guards, resized via the SOAK_* env
# knobs — hours of wall clock, opt-in before capacity-sensitive
# releases, deliberately NOT a CI job (docs/OPERATIONS.md
# "Capacity & SLOs")
soak-full:
	SOAK_JOBS=100000 SOAK_WORKERS=3 SOAK_PUBLISH_RATE=60 \
	SOAK_MAX_WALL=7200 SOAK_KILLS=20 SOAK_KILL_INTERVAL=120 \
	python -m pytest tests/test_soak.py::test_soak_full -v -m slow

# incident plane suite (ISSUE 18): bundle-schema freeze (fields never
# renumbered/retyped; the checked-in v1 fixture must keep loading and
# compiling), compile_bundle purity + window re-anchoring (no sleeps,
# per the window_active/flap_on discipline), breach-signature diffing,
# the auto-export ring, the /v1/incidents degradation contract, and
# the fuzzer's determinism
incident:
	python -m pytest tests/test_incident.py -v

# seeded incident-scenario fuzzer (ISSUE 18 stretch): mutates the
# fixture bundle's compiled plan (shift windows, swap fault kinds,
# scale job counts) and replays each variant on a fresh SoakRig fleet
# hunting for NEW breach signatures — minutes per variant, opt-in,
# deliberately NOT a CI job (like soak-full).  Re-run any campaign
# with the same --seed to reproduce it; drop --execute (edit below)
# to just print the bred variants.
fuzz-scenarios:
	python -m downloader_tpu.incident.fuzz --seed 1818 --variants 4 --execute

# SLO plane suite (ISSUE 15): burn-rate/budget math against
# hand-computed windows, settle classification, the /readyz slo block,
# heartbeat digests + the aggregated fleet overview (mixed-shape
# compat, brownout-bounded peer/coord queries, degradation contract),
# per-hop budget guard, and the 3-worker fleet-overview acceptance run
slo:
	python -m pytest tests/test_slo.py tests/test_overview.py -v

# sharded compute plane suite (ISSUE 16): the pjit/shard_map chooser
# (decisions pinned per (shape, mesh)), the regex->PartitionSpec table
# (every upscaler param matches exactly one rule, unmatched raises),
# buffer donation, the double-buffered TransferQueue, hop billing, and
# the mesh-reshape parity tests ({'data':4,'model':2} vs
# {'data':2,'model':4} produce identical losses and updated params)
compute-shard:
	python -m pytest tests/test_compute_shard.py tests/test_multichip.py -v

# graftlint (downloader_tpu/analysis, docs/ANALYSIS.md): the repo-
# invariant static analyzer over the full tree (JSON for CI parsing),
# then the tier-1 gate (zero unsuppressed findings + <10 s budget +
# registry fixtures)
lint:
	python -m downloader_tpu.analysis --json
	python -m pytest tests/test_lint.py tests/test_analysis.py -q

bench:
	python bench.py

# standalone streaming-vs-barrier stage-overlap bench (one JSON line:
# stage_overlap_speedup must stay >= 1.25, time_to_staged_ms alongside)
bench-overlap:
	python bench.py --overlap

# standalone fleet-coordination bench (one JSON line: M workers x same
# hot content, fleet_origin_bytes_ratio must stay >= 2.0; plus the v22
# weak-scaling arm — fleet_scaling_ratio, 1 -> 3 worker throughput on
# a same-content-heavy workload, must stay >= 0.8x linear)
bench-fleet:
	python bench.py --fleet

# standalone multi-tenant fairness bench (one JSON line: a saturating
# BULK tenant must not degrade a HIGH tenant's p99 time-to-staged by
# more than 1.25x vs the idle-worker baseline)
bench-fairness:
	python bench.py --fairness

# standalone crash-durability bench (one JSON line: journal_overhead_ms
# must stay < 1 ms/job; restart_recovery_ms = SIGKILL -> restart ->
# recovered job DONE through a real worker subprocess)
bench-crash:
	python bench.py --crash

# standalone fleet-observability bench (one JSON line: hop-ledger and
# trace-propagation A-B overheads must each stay < 1 ms/job;
# hop_ledger_coverage = summed hop seconds / stage wall on a real
# end-to-end job, must stay within 5% of 1.0)
bench-obs:
	python bench.py --obs

# standalone origin-plane racing bench (one JSON line: with one fast +
# one throttled mirror, racing must beat the slow origin alone by
# >= 1.5x AND stay within 10% of the fast origin alone)
bench-racing:
	python bench.py --racing

# standalone sustained-load soak bench (one JSON line: soak_ok = every
# SLO guard green over the mixed-workload + kill-chaos run; soak_p99_ms,
# soak_rss_slope_mb_per_kjob, soak_journal_peak_bytes alongside)
bench-soak:
	python bench.py --soak

# standalone degraded-world soak bench (one JSON line: degraded_ok =
# every SLO guard green under the stall + brownout scenario;
# brownout_shed_ms = brownout onset -> slow-opened breaker;
# split_brain_stale_writes must stay 0)
bench-degraded:
	python bench.py --degraded

# standalone storage-fault-plane bench (one JSON line: disk_ok = every
# SLO guard green under the windowed ENOSPC brownout — including zero
# corrupt bytes served — AND the scrubber repaired every seeded bit-rot
# flip with zero quarantines; disk_scrub_repaired /
# disk_scrub_quarantined / disk_corrupt_bytes_served alongside)
bench-disk:
	python bench.py --disk

# standalone SLO-plane bench (one JSON line: slo_overhead_ms must stay
# < 1 ms/job; fleet_overview_age_s must sit under 2x the heartbeat
# interval in steady state; hop_budget_ok = every hop inside its
# BASELINE_HOPS.json budget, failures name the guilty hop)
bench-slo:
	python bench.py --slo

# standalone zero-copy staging A/B (one JSON line:
# zerocopy_cpu_ratio = buffered-path CPU per staged GB / zero-copy-path
# CPU per staged GB on the same calibration job — > 1.0 means the
# mmap/sendfile upload path is cheaper per byte; a ratio sliding to
# 1.0 flags a quietly re-introduced buffered copy)
bench-zerocopy:
	python bench.py --zerocopy

# standalone incident round-trip bench (one JSON line:
# incident_replay_signature_match = a degraded-world breach bundle,
# compiled and replayed on 2 consecutive fresh fleets, reproduced its
# breach signature with zero stale split-brain writes — the ISSUE 18
# acceptance guard)
bench-incident:
	python bench.py --incident

# standalone sharded-compute bench (one JSON line:
# multichip_scaling_efficiency = single-device wall / data=4-sharded
# wall for the same total batch on the dry-run mesh, must stay >= 0.8
# — virtual devices share one CPU, so this bounds sharding OVERHEAD)
bench-multichip:
	python bench.py --multichip

# regenerate protobuf gencode (no protoc in the image: the script
# applies the declarative edits in scripts/gen_proto.py to the current
# serialized descriptor and re-emits downloader_pb2.py; keep
# downloader.proto in sync by hand).  tests/test_schemas.py guards
# against the committed module drifting from this output.
proto:
	python scripts/gen_proto.py

run:
	python -m downloader_tpu

docker:
	docker build -t downloader-tpu .
